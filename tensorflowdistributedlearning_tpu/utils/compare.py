"""Best-model comparison (reference: utils.py:11-28).

The reference's ``metric_comparisson(greater_is_better=True)`` returned
``best > current`` — i.e. it told BestExporter to export when the NEW result was WORSE
(reference: utils.py:23-28 against BestExporter's "True => current is better" contract).
This implementation returns the comparison the right way around.
"""

from __future__ import annotations

from typing import Mapping


def metric_comparison(
    best_eval_result: Mapping[str, float],
    current_eval_result: Mapping[str, float],
    key: str = "metrics/mean_iou",
    greater_is_better: bool = True,
) -> bool:
    """True iff ``current_eval_result[key]`` improves on ``best_eval_result[key]``."""
    if not best_eval_result or key not in best_eval_result:
        raise ValueError(f"best_eval_result cannot be empty and must contain {key!r}")
    if not current_eval_result or key not in current_eval_result:
        raise ValueError(f"current_eval_result cannot be empty and must contain {key!r}")
    if greater_is_better:
        return current_eval_result[key] > best_eval_result[key]
    return current_eval_result[key] < best_eval_result[key]
