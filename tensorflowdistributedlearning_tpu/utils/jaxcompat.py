"""Version shims for the jax APIs this framework uses.

The codebase targets the modern ``jax.shard_map`` entry point (top-level,
keyword-only, ``axis_names`` selecting the MANUAL axes). Older jax builds
(<0.5) ship the same machinery as ``jax.experimental.shard_map.shard_map``
with the complementary ``auto`` parameter (the axes that are NOT manual).
``install()`` bridges the two so one source tree runs on both: on an old jax
it publishes a ``jax.shard_map`` that translates ``axis_names`` →
``auto = mesh axes − axis_names`` (and ``check_vma`` → ``check_rep``).

The hybrid auto-axis mode is NOT bridged: lowering it through the legacy
backend has been observed to SIGABRT the process (XLA:CPU, jax 0.4.37), so
the shim refuses it eagerly with ``NotImplementedError`` — the same tests
that could not run at seed (top-level ``jax.shard_map`` absent) still cannot,
but now they fail cleanly instead of crashing the suite.

Known residual gap on the bridge: the GPipe pipeline step's cross-stage
gradient assembly relies on vma-aware transposition over the MODEL axis
(auto-psum of slot-structured cotangents, shared-param cotangents taken
once); without vma tracking its one-step parity vs the plain step does not
hold exactly (the pipelined e2e runs still learn — see
tests/test_pipeline_{vit,xception}.py for which claims are pinned where).
"""

from __future__ import annotations

import functools

import jax

# True when install() published the legacy shard_map bridge: the build has no
# varying-manual-axes (vma) tracking, so code that branches on vma_of() must
# assume every value inside shard_map is per-shard varying (see
# train/step.py:_mean_grads — on vma builds the automatic transposition
# psums unvarying cotangents; on legacy builds nothing does, and treating a
# per-shard gradient as already-reduced mis-scales or sign-flips updates).
LEGACY_BRIDGE = False


def install() -> None:
    """Publish ``jax.shard_map`` / ``jax.lax.axis_size`` on builds that
    predate them. Idempotent; a no-op on modern jax."""
    global LEGACY_BRIDGE
    _install_axis_size()
    _install_pvary()
    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except ImportError:  # neither spelling: let call sites raise naturally
        return
    LEGACY_BRIDGE = True

    def shard_map(
        f=None,
        *,
        mesh,
        in_specs,
        out_specs,
        axis_names=None,
        check_vma=None,
        **kwargs,
    ):
        if f is None:  # decorator-factory form: @shard_map(mesh=..., ...)
            return functools.partial(
                shard_map,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                axis_names=axis_names,
                check_vma=check_vma,
                **kwargs,
            )
        if axis_names is not None:
            auto = frozenset(set(mesh.axis_names) - set(axis_names))
            if auto:
                # hybrid manual/auto mode on the legacy backend is not just
                # unimplemented — lowering it has been observed to SIGABRT the
                # process (XLA:CPU, jax 0.4.37). Refuse at the API boundary so
                # callers get a clean Python error instead of a crashed run.
                raise NotImplementedError(
                    "shard_map(axis_names=...) with auto (non-manual) mesh "
                    f"axes {sorted(auto)} requires a jax build with native "
                    "jax.shard_map support; this legacy-bridge build "
                    f"(jax {jax.__version__}) only runs fully-manual shard_map"
                )
            kwargs.setdefault("auto", auto)
        if check_vma is not None:
            kwargs.setdefault("check_rep", check_vma)
        # the legacy rep-checker cannot infer replication through the
        # psum/pmean patterns the modern vma tracker validates (it rejects
        # correct steps with "could only infer replication over ..."), so the
        # bridge runs unchecked — numerics are pinned by the oracle tests,
        # not the static checker
        kwargs.setdefault("check_rep", False)
        return _legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )

    jax.shard_map = shard_map


def _install_pvary() -> None:
    """``jax.lax.pvary`` (and its successor ``pcast``) mark a value as varying
    over manual axes for the vma tracker. Builds that predate BOTH have no
    varying-type system at all, so the marking is semantically an identity —
    publish it as one so vma-aware call sites run unchanged."""
    if hasattr(jax.lax, "pvary") or hasattr(jax.lax, "pcast"):
        return

    def pvary(x, axis_names):  # noqa: ARG001 — identity without vma tracking
        return x

    jax.lax.pvary = pvary


def _install_axis_size() -> None:
    """``jax.lax.axis_size(name_or_names)`` (modern) ← ``jax.core.axis_frame``
    (which returns the bound size directly on old builds)."""
    if hasattr(jax.lax, "axis_size"):
        return

    def axis_size(axis_name) -> int:
        if isinstance(axis_name, (tuple, list)):
            size = 1
            for name in axis_name:
                size *= jax.core.axis_frame(name)
            return size
        return jax.core.axis_frame(axis_name)

    jax.lax.axis_size = axis_size
