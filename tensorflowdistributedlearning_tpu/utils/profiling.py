"""Tracing / profiling utilities.

The reference had NO profiler integration — its only performance artifacts were a
param-count probe and docstring notes ("NCHW ~10% faster", reference: model.py:45-46,
444-445; SURVEY §5.1). This module supplies the subsystem the reference lacked:

- ``trace``: context manager around ``jax.profiler`` writing TensorBoard-viewable
  traces (XLA op timeline, HBM usage) to a log dir;
- ``StepTimer``: wall-clock per-step timing with a sync that is robust on tunneled
  TPU backends (pulls a scalar with ``device_get`` — ``block_until_ready`` alone has
  been observed to return before remote execution finishes);
- ``annotate``: named trace spans (``jax.profiler.TraceAnnotation``) so host-side
  phases (decode, shard, step) are visible in the timeline.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, List, Optional

import jax
import numpy as np


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a ``jax.profiler`` trace for the enclosed block; view with
    TensorBoard's profile plugin pointed at ``logdir``."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span visible in profiler timelines (host + device)."""
    return jax.profiler.TraceAnnotation(name)


def sync(tree: Any) -> None:
    """Force completion of every array in ``tree``. Uses ``device_get`` on one leaf
    (full-result fetch) plus ``block_until_ready`` on the rest."""
    leaves = [x for x in jax.tree.leaves(tree) if isinstance(x, jax.Array)]
    if not leaves:
        return
    jax.block_until_ready(leaves)
    # the cross-host/tunnel-safe barrier: an actual value fetch
    np.asarray(jax.device_get(leaves[0]))


class StepTimer:
    """Accumulates per-step wall times; ``summary()`` reports
    mean/p50/p90/p99 and optional items/sec. Synchronization is the caller's
    choice: pass the step output to ``stop`` and it is ``sync``'d before the
    clock stops.

    The samples live in an ``obs.metrics.TimeHistogram`` and the percentile
    math is ``obs.metrics.time_summary`` — the ONE step-timing implementation
    the telemetry spans, the benchmarks (bench.py), and this timer share."""

    def __init__(self, items_per_step: Optional[int] = None):
        from tensorflowdistributedlearning_tpu.obs.metrics import TimeHistogram

        self.items_per_step = items_per_step
        self._hist = TimeHistogram("step")
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, outputs: Any = None) -> float:
        if self._t0 is None:
            raise RuntimeError("StepTimer.stop() without start()")
        if outputs is not None:
            sync(outputs)
        dt = time.perf_counter() - self._t0
        self._hist.record(dt)
        self._t0 = None
        return dt

    @contextlib.contextmanager
    def step(self):
        """``with timer.step(): out = train_step(...); sync(out)`` — the CALLER must
        sync inside the block (or use start()/stop(outputs) which syncs for you);
        otherwise only async dispatch is measured."""
        self.start()
        yield
        self.stop()

    @property
    def times(self) -> List[float]:
        return self._hist.samples

    def summary(self, skip_first: int = 1) -> Dict[str, float]:
        """Timing stats, excluding the first ``skip_first`` (compile) steps."""
        if not len(self._hist):
            raise RuntimeError("StepTimer.summary(): no steps recorded")
        out = self._hist.summary(skip_first=skip_first)
        out["steps"] = out.pop("count")
        if self.items_per_step:
            out["items_per_sec"] = self.items_per_step / out["mean_s"]
        return out


def memory_stats() -> Dict[str, Dict[str, int]]:
    """Per-device live memory statistics (bytes) — the HBM observability knob
    for sizing batch/remat/parallelism choices. Keys are device strings; values
    are whatever the backend reports (TPU: ``bytes_in_use``, ``peak_bytes_in_use``,
    ``bytes_limit``, ...). Devices whose runtime does not implement the query
    (e.g. some CPU builds) are simply absent."""
    import jax

    out: Dict[str, Dict[str, int]] = {}
    for device in jax.local_devices():
        stats = getattr(device, "memory_stats", None)
        if stats is None:
            continue
        try:
            value = stats()
        except Exception:  # noqa: BLE001 — unsupported backend
            continue
        if value:
            out[str(device)] = dict(value)
    return out


def log_memory(logger_fn=None) -> Dict[str, Dict[str, int]]:
    """Log (and return) a compact per-device HBM summary: in-use / peak / limit."""
    import logging as _logging

    log = logger_fn or _logging.getLogger(__name__).info
    stats = memory_stats()
    for dev, s in stats.items():
        in_use = s.get("bytes_in_use", 0)
        peak = s.get("peak_bytes_in_use", 0)
        limit = s.get("bytes_limit", 0)
        log(
            "%s: %.1f MiB in use (peak %.1f MiB, limit %.1f MiB)",
            dev, in_use / 2**20, peak / 2**20, limit / 2**20,
        )
    return stats
