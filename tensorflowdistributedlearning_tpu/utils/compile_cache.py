"""Persistent XLA compile-cache plumbing: the load-not-compile layer.

Compilation is the biggest cold-start cliff in the stack: every serve
replica recompiles its bucket ladder, every elastic resize recompiles the
step function at the new world size, every train restart pays full warmup.
JAX ships a content-addressed persistent compilation cache (keyed on the
canonicalized StableHLO module + jaxlib version + registered XLA flags +
compile options + device kinds); this module wires it through the CLI
surface (``--compile-cache-dir`` on train/fit/serve/serve-fleet) and turns
its hit/miss stream into telemetry the rest of obs/ can ledger.

Three public seams:

- :func:`configure` points the process at a cache directory, forcing the
  cache-everything knobs (JAX's defaults skip sub-second compiles, which on
  CPU smoke scale means caching *nothing*). Unwritable directory degrades
  to a warning + uncached run — a bad ``--compile-cache-dir`` must never
  kill a training job.
- :func:`consume_pending` is called by ``obs.recompile`` exactly once per
  backend-compile event to learn whether that compile was served from the
  cache (and how much compile time the hit saved). JAX fires the cache-hit
  monitoring events synchronously on the compiling thread *before* the
  compile-duration event closes, so a thread-local carries the verdict
  across the two listener callbacks.
- :func:`fingerprint` / :func:`merge` support shipping a cache subdir
  beside an exported serving artifact (manifest records the fingerprint;
  serve merges the entries into its active cache before warmup).

Cache-key caveat (documented, load-bearing): keys hash the canonicalized
module, jaxlib version, registered XLA flags, compile options AND the
serialized backend topology — which is PROCESS-LOCAL: it covers the total
device count and which devices belong to this process, so two processes
only share entries when their whole topology matches rank-for-rank
(verified empirically: rank 0 and rank 1 of the same 2-process world
compute *different* keys for the same module). Consequences wired through
this codebase: (1) the elastic AOT standby is a real (world-1)-process
mini-world, not a solo emulator; (2) ``attach_compile_cache`` compiles the
serving ladder in a 1-device subprocess because replicas load under the
serving topology, not the trainer's; (3) ``configure`` disables the XLA
autotune-cache debug option, whose directory (a path inside cache_dir)
would otherwise be hashed into every key, pinning entries to one absolute
cache path. Keys do NOT survive jaxlib upgrades or XLA flag changes.
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
import tempfile
import threading
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

# jax.monitoring event names fired by jax._src.compiler.compile_or_get_cached
# (verified against the installed jax; literal strings are the stable API)
_REQUEST_EVENT = "/jax/compilation_cache/compile_requests_use_cache"
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_SAVED_EVENT = "/jax/compilation_cache/compile_time_saved_sec"

try:
    from jax import monitoring as _monitoring
except Exception:  # noqa: BLE001 — jax without the monitoring API
    _monitoring = None

_lock = threading.Lock()
_listener_registered = False
_active_dir: Optional[str] = None

# Per-thread in-flight verdict: compile_or_get_cached fires request → (hit,
# saved) → the backend-compile duration event, all on the compiling thread,
# so thread-local state bridges them without cross-compile races even under
# the parallel warmup pool.
_tls = threading.local()

# Process-wide counters (updated by the listeners on every compile) for
# introspection and the run_end summary; guarded by _lock. "misses" is
# derived as requests - hits at stats() read time.
_stats: Dict[str, float] = {"requests": 0, "hits": 0, "saved_s": 0.0}


def _on_record_event(event: str, **kwargs) -> None:
    # Stats are counted here, in the listener, not in consume_pending():
    # consume_pending() only runs when an obs.recompile detector is attached,
    # and a bare process (serve replica without telemetry, standby sidecar)
    # must still report accurate hit/miss counts via stats().
    if event == _REQUEST_EVENT:
        _tls.pending_request = True
        with _lock:
            _stats["requests"] += 1
    elif event == _HIT_EVENT:
        _tls.pending_hit = True
        with _lock:
            _stats["hits"] += 1


def _on_duration_event(event: str, duration_secs: float, **kwargs) -> None:
    if event == _SAVED_EVENT:
        _tls.pending_saved_s = float(duration_secs)
        with _lock:
            _stats["saved_s"] += float(duration_secs)


def _ensure_listeners() -> bool:
    """Register the cache-hit monitoring listeners once per process."""
    global _listener_registered
    if _monitoring is None:
        return False
    with _lock:
        if _listener_registered:
            return True
        try:
            _monitoring.register_event_listener(_on_record_event)
            _monitoring.register_event_duration_secs_listener(
                _on_duration_event
            )
        except Exception as e:  # noqa: BLE001 — degrade, never crash
            logger.warning("compile-cache hit telemetry unavailable: %s", e)
            return False
        _listener_registered = True
    return True


def consume_pending() -> Tuple[Optional[bool], float]:
    """Pop this thread's in-flight cache verdict.

    Returns ``(cache_hit, saved_s)`` where ``cache_hit`` is ``None`` when
    the persistent cache was not consulted for the compile that just closed
    (cache disabled, or key generation failed), ``True`` on a hit (with the
    compile time the hit saved), ``False`` on a genuine miss. Called by
    ``obs.recompile._dispatch`` exactly once per backend-compile event.
    """
    requested = getattr(_tls, "pending_request", False)
    hit = getattr(_tls, "pending_hit", False)
    saved_s = getattr(_tls, "pending_saved_s", 0.0)
    _tls.pending_request = False
    _tls.pending_hit = False
    _tls.pending_saved_s = 0.0
    if not requested:
        return None, 0.0
    return (True, saved_s) if hit else (False, 0.0)


def stats() -> Dict[str, float]:
    """Process-wide hit/miss counters (every compile the listeners saw)."""
    with _lock:
        out = dict(_stats)
    out["misses"] = out["requests"] - out["hits"]
    return out


def reset_stats() -> None:
    with _lock:
        for k in _stats:
            _stats[k] = 0.0 if k == "saved_s" else 0


def active_dir() -> Optional[str]:
    """The cache directory this process was configured with (None = off)."""
    return _active_dir


def _probe_writable(cache_dir: str) -> bool:
    try:
        os.makedirs(cache_dir, exist_ok=True)
        fd, probe = tempfile.mkstemp(prefix=".cache_probe_", dir=cache_dir)
        os.close(fd)
        os.unlink(probe)
        return True
    except OSError:
        return False


def configure(cache_dir: Optional[str]) -> bool:
    """Point this process's XLA compiles at a persistent cache directory.

    Must run before the first compile to catch everything, but is safe (and
    effective for later compiles) at any point — an already-initialized
    cache backend is reset so the new directory takes. Forces the
    cache-everything knobs: JAX's defaults skip compiles under 1 s and tiny
    entries, which at CPU-smoke scale silently caches nothing.

    Returns True when the cache is active. An unwritable/uncreatable
    directory logs a warning and returns False with the process left
    uncached — degradation, never a crash. ``cache_dir=None`` is a no-op
    False (callers can pass the knob through unconditionally).
    """
    global _active_dir
    if not cache_dir:
        return False
    cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
    if not _probe_writable(cache_dir):
        logger.warning(
            "compile cache dir %s is not writable — proceeding UNCACHED "
            "(every compile will be paid in full)",
            cache_dir,
        )
        return False
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache EVERYTHING: the defaults (min 1.0s compile, min entry size)
        # are tuned for real accelerators and would skip our smoke compiles
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # The default enables the XLA per-fusion autotune cache, whose
        # directory (a path INSIDE cache_dir) is baked into compile options
        # and is NOT stripped from the cache key — so keys would depend on
        # the cache dir's absolute path and entries shipped beside an
        # artifact could never hit. Disable it; it's a GPU-only feature.
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
    except Exception as e:  # noqa: BLE001 — old jax without the knobs
        logger.warning("persistent compile cache unavailable: %s", e)
        return False
    # The cache backend latches on first compile: _cache_initialized flips
    # True even when the dir was unset (leaving _cache None *permanently*),
    # so a late configure() must reset unconditionally — checking _cache
    # alone misses the initialized-while-disabled state.
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — private seam; best-effort
        pass
    _ensure_listeners()
    _active_dir = cache_dir
    logger.info("persistent compile cache at %s", cache_dir)
    return True


# -- artifact cache subdir support ------------------------------------------


def fingerprint(cache_dir: str) -> Dict[str, object]:
    """Content fingerprint of a cache directory for manifest stamping.

    Hashes the sorted (relative path, size) list — cheap, order-stable, and
    enough to detect a truncated/mixed copy. Entry *contents* are already
    content-addressed by JAX's own key, so hashing bytes again buys nothing.
    """
    entries = []
    if os.path.isdir(cache_dir):
        for root, _dirs, files in os.walk(cache_dir):
            for name in sorted(files):
                path = os.path.join(root, name)
                rel = os.path.relpath(path, cache_dir)
                try:
                    entries.append((rel, os.path.getsize(path)))
                except OSError:
                    continue
    entries.sort()
    h = hashlib.sha256()
    for rel, size in entries:
        h.update(f"{rel}\x00{size}\n".encode())
    return {"entries": len(entries), "fingerprint": h.hexdigest()}


def merge(src_dir: str, dst_dir: str) -> int:
    """Copy cache entries from ``src_dir`` into ``dst_dir`` (skip existing).

    Used by serve to fold an artifact's shipped cache subdir into the
    replica's active cache directory so warmup loads instead of compiling.
    Returns the number of entries copied; I/O failures skip the entry (a
    missed merge costs one compile, not the replica).
    """
    copied = 0
    if not os.path.isdir(src_dir):
        return 0
    for root, _dirs, files in os.walk(src_dir):
        for name in files:
            src = os.path.join(root, name)
            rel = os.path.relpath(src, src_dir)
            dst = os.path.join(dst_dir, rel)
            if os.path.exists(dst):
                continue
            try:
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                shutil.copy2(src, dst)
                copied += 1
            except OSError as e:
                logger.warning("cache merge skipped %s: %s", rel, e)
    return copied
