"""Device discovery (reference: utils.py:6-8 filtered ``device_lib.list_local_devices``
for GPUs; the TPU-native equivalent asks the JAX runtime)."""

from __future__ import annotations

from typing import List, Optional

import jax


def get_available_devices(platform: Optional[str] = None) -> List[str]:
    """Return device name strings, e.g. ``['TPU:0', 'TPU:1']``.

    ``platform`` filters like the reference filtered ``device_type == 'GPU'``.
    """
    devices = jax.devices() if platform is None else jax.devices(platform)
    return [f"{d.platform.upper()}:{d.id}" for d in devices]
