"""Device discovery (reference: utils.py:6-8 filtered ``device_lib.list_local_devices``
for GPUs; the TPU-native equivalent asks the JAX runtime)."""

from __future__ import annotations

from typing import List, Optional

import jax


def get_available_devices(platform: Optional[str] = None) -> List[str]:
    """Return device name strings, e.g. ``['TPU:0', 'TPU:1']``.

    ``platform`` filters like the reference filtered ``device_type == 'GPU'``.
    """
    devices = jax.devices() if platform is None else jax.devices(platform)
    return [f"{d.platform.upper()}:{d.id}" for d in devices]


def apply_platform_env() -> None:
    """Honor ``JAX_PLATFORMS`` even when a site hook pre-imported jax with
    another platform (this image's axon sitecustomize does): env vars alone are
    too late once the platform choice is cached, but the config route works
    because backend initialization itself is lazy. Call at the top of any
    standalone driver/script; the CLI does this automatically."""
    import os

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        try:
            jax.config.update("jax_platforms", platforms)
        except Exception:  # noqa: BLE001 — never block a driver on this nicety
            pass
