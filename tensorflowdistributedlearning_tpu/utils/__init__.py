from tensorflowdistributedlearning_tpu.utils.devices import get_available_devices
from tensorflowdistributedlearning_tpu.utils.compare import metric_comparison
from tensorflowdistributedlearning_tpu.utils.params import count_params

__all__ = ["get_available_devices", "metric_comparison", "count_params"]
