"""Minimal, dependency-free TensorBoard event-file writer.

The reference's observability was TensorBoard summaries written by manual
``SummarySaverHook``s — train scalars + image grids every 20 steps to
``fold{i}/train``, eval images every step to ``fold{i}/eval``, with automatic
summaries disabled so train and eval curves share plots (reference:
model.py:405-481, 120). This module reproduces those event files WITHOUT importing
TensorFlow: it hand-encodes the two tiny protobuf messages TensorBoard reads
(``Event`` wrapping ``Summary``) and frames them as TFRecords with masked CRC-32C —
the on-disk format is byte-compatible with what ``tf.summary.FileWriter`` produced.

Wire schema encoded here (field numbers from the public tensorboard .protos):
  Event:   1=wall_time(double) 2=step(int64) 5=summary(message)
  Summary: 1=repeated Value;  Value: 1=tag(string) 2=simple_value(float)
                                     4=image(message)
  Image:   1=height 2=width 3=colorspace 4=encoded_image_string(PNG bytes)
"""

from __future__ import annotations

import os
import struct
import time
from typing import Dict, Optional, Union

import numpy as np

# -- protobuf wire-format primitives ----------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire_type: int) -> bytes:
    return _varint((field << 3) | wire_type)


def _field_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value)


def _field_double(field: int, value: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", value)


def _field_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


def _field_bytes(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(value)) + value


# -- CRC-32C (Castagnoli), table-driven, with the TFRecord mask --------------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _tfrecord(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (
        header
        + struct.pack("<I", _masked_crc(header))
        + payload
        + struct.pack("<I", _masked_crc(payload))
    )


# -- summary message builders ------------------------------------------------


def _scalar_value(tag: str, value: float) -> bytes:
    body = _field_bytes(1, tag.encode()) + _field_float(2, float(value))
    return _field_bytes(1, body)  # Summary.value


def _encode_png(image: np.ndarray) -> bytes:
    from io import BytesIO

    from PIL import Image

    buf = BytesIO()
    Image.fromarray(image).save(buf, format="PNG")
    return buf.getvalue()


def _image_value(tag: str, image: np.ndarray) -> bytes:
    """``image``: [H, W] or [H, W, C] float in [0,1] or uint8."""
    if image.dtype != np.uint8:
        image = (np.clip(image, 0.0, 1.0) * 255.0).astype(np.uint8)
    if image.ndim == 3 and image.shape[-1] == 1:
        image = image[..., 0]
    h, w = image.shape[0], image.shape[1]
    colorspace = 1 if image.ndim == 2 else image.shape[-1]
    img_msg = (
        _field_varint(1, h)
        + _field_varint(2, w)
        + _field_varint(3, colorspace)
        + _field_bytes(4, _encode_png(image))
    )
    body = _field_bytes(1, tag.encode()) + _field_bytes(4, img_msg)
    return _field_bytes(1, body)


def _event(step: int, summary_body: bytes, wall_time: Optional[float] = None) -> bytes:
    return (
        _field_double(1, wall_time if wall_time is not None else time.time())
        + _field_varint(2, step)
        + _field_bytes(5, summary_body)
    )


# -- public writer -----------------------------------------------------------


class SummaryWriter:
    """Append-only TensorBoard event file in ``logdir`` (one per writer, created with
    the conventional ``events.out.tfevents.{ts}.{host}`` name)."""

    def __init__(self, logdir: str):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.{os.uname().nodename}"
        self._f = open(os.path.join(logdir, fname), "ab")
        # file-version header event, as the TF writer emits
        header = _field_double(1, time.time()) + _field_bytes(
            3, b"brain.Event:2"
        )
        self._f.write(_tfrecord(header))
        self._f.flush()

    def scalar(self, tag: str, value: float, step: int) -> None:
        self._f.write(_tfrecord(_event(step, _scalar_value(tag, value))))

    def scalars(self, values: Dict[str, float], step: int) -> None:
        body = b"".join(_scalar_value(t, v) for t, v in values.items())
        self._f.write(_tfrecord(_event(step, body)))

    def image(self, tag: str, image: np.ndarray, step: int) -> None:
        """One image summary (the reference summarized input/label/probability/
        prediction grids, model.py:405-426)."""
        self._f.write(_tfrecord(_event(step, _image_value(tag, np.asarray(image)))))

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.flush()
        self._f.close()


def read_events(path: str):
    """Parse an event file back into [(step, {tag: value})] for scalars — used by
    tests to round-trip the writer without TensorBoard installed."""
    out = []
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos + 12 <= len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        payload = data[pos + 12 : pos + 12 + length]
        pos += 12 + length + 4
        step, scalars = _parse_event(payload)
        if scalars:
            out.append((step, scalars))
    return out


def _parse_event(payload: bytes):
    step, scalars = 0, {}
    pos = 0
    while pos < len(payload):
        key, pos = _read_varint(payload, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = _read_varint(payload, pos)
            if field == 2:
                step = val
        elif wt == 1:
            pos += 8
        elif wt == 5:
            pos += 4
        elif wt == 2:
            ln, pos = _read_varint(payload, pos)
            chunk = payload[pos : pos + ln]
            pos += ln
            if field == 5:  # summary
                scalars.update(_parse_summary(chunk))
    return step, scalars


def _parse_summary(data: bytes) -> Dict[str, float]:
    out: Dict[str, float] = {}
    pos = 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        field, wt = key >> 3, key & 7
        if wt == 2:
            ln, pos = _read_varint(data, pos)
            chunk = data[pos : pos + ln]
            pos += ln
            if field == 1:  # Value
                tag, val = None, None
                p = 0
                while p < len(chunk):
                    k, p = _read_varint(chunk, p)
                    f, w = k >> 3, k & 7
                    if w == 2:
                        l2, p = _read_varint(chunk, p)
                        if f == 1:
                            tag = chunk[p : p + l2].decode()
                        p += l2
                    elif w == 5:
                        if f == 2:
                            (val,) = struct.unpack_from("<f", chunk, p)
                        p += 4
                    elif w == 1:
                        p += 8
                    elif w == 0:
                        _, p = _read_varint(chunk, p)
                if tag is not None and val is not None:
                    out[tag] = val
        elif wt == 0:
            _, pos = _read_varint(data, pos)
        elif wt == 1:
            pos += 8
        elif wt == 5:
            pos += 4
    return out


def _read_varint(data: bytes, pos: int):
    result = shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
