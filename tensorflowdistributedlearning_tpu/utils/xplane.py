"""XPlane profile reader: per-op device-time breakdown without TensorFlow.

``jax.profiler.start_trace`` writes its device timeline as an ``XSpace``
protocol buffer (``*.xplane.pb``). The stock consumer is TensorBoard's profile
plugin — a TensorFlow dependency this framework doesn't carry. This module
reads the wire format directly (protobuf is length-delimited tag/value pairs;
the XPlane schema is public: tensorflow/tsl ``profiler/protos/xplane.proto``)
and aggregates per-op device time, so "where does the step time go" is
answerable on any machine the trace was captured on.

The reference had no profiler story at all (SURVEY §5.1); TensorBoard-free
trace reading is the subsystem that closes the loop the other way — not just
writing traces (``utils.profiling.trace``) but deciding from them.

Usage::

    from tensorflowdistributedlearning_tpu.utils import profiling, xplane
    with profiling.trace(logdir):
        run_steps()
    for row in xplane.op_breakdown(logdir)[:20]:
        print(row.name, row.total_ms, row.occurrences)

or ``python -m tensorflowdistributedlearning_tpu.utils.xplane <logdir>``.
"""

from __future__ import annotations

import dataclasses
import glob
import os
from typing import Dict, Iterator, List, Optional, Tuple

# -- protobuf wire-format scanner -------------------------------------------

_WIRE_VARINT = 0
_WIRE_FIXED64 = 1
_WIRE_BYTES = 2
_WIRE_FIXED32 = 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 70:  # negative int64s legitimately take 10 bytes
            raise ValueError("varint overflow (corrupt protobuf)")


def _fields(buf) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a serialized message
    (``bytes`` or ``memoryview``). BYTES fields yield memoryview slices, and
    nested messages feed them straight back in — zero-copy end to end (traces
    reach 100s of MB)."""
    view = memoryview(buf)
    pos = 0
    end = len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == _WIRE_VARINT:
            value, pos = _read_varint(buf, pos)
        elif wire == _WIRE_BYTES:
            length, pos = _read_varint(buf, pos)
            value = view[pos : pos + length]
            pos += length
        elif wire == _WIRE_FIXED64:
            value = int.from_bytes(view[pos : pos + 8], "little")
            pos += 8
        elif wire == _WIRE_FIXED32:
            value = int.from_bytes(view[pos : pos + 4], "little")
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, value


# -- XPlane schema (field numbers from tsl's xplane.proto) -------------------

# XSpace: planes = 1
# XPlane: id=1, name=2, lines=3, event_metadata=4 (map), stat_metadata=5 (map)
# XLine:  id=1, name=2, timestamp_ns=3, events=4
# XEvent: metadata_id=1, offset_ps=2, duration_ps=3, stats=4, num_occurrences=5
# XEventMetadata: id=1, name=2
# map entry: key=1, value=2


def _parse_event_metadata(plane_buf) -> Dict[int, str]:
    names: Dict[int, str] = {}
    for field, _, value in _fields(plane_buf):
        if field != 4:
            continue
        key = None
        meta_name = ""
        for f2, _, v2 in _fields(value):
            if f2 == 1:
                key = v2
            elif f2 == 2:
                meta_id = None
                for f3, _, v3 in _fields(v2):
                    if f3 == 1:
                        meta_id = v3
                    elif f3 == 2:
                        meta_name = bytes(v3).decode("utf-8", "replace")
                if key is None:
                    key = meta_id
        if key is not None:
            names[key] = meta_name
    return names


@dataclasses.dataclass
class OpTime:
    name: str
    total_ms: float
    occurrences: int
    # share of the aggregated op time across every matched plane/file (on a
    # multi-chip capture that is fleet time, not one chip's step time)
    fraction: float


@dataclasses.dataclass
class PlaneBreakdown:
    plane: str
    total_ms: float
    ops: List[OpTime]


def _plane_name(plane_buf) -> str:
    """The plane's name alone — a cheap top-level scan (length-delimited
    payloads are skipped, not decoded) so callers can reject planes by name
    without paying for a full :func:`_parse_plane`."""
    for field, _, value in _fields(plane_buf):
        if field == 2:
            return bytes(value).decode("utf-8", "replace")
    return ""


def _parse_plane(
    plane_buf,
) -> Tuple[str, Dict[str, Dict[str, List[float]]]]:
    """(plane_name, {line_name: {event_name: [duration_ms, occurrences]}}).

    Lines stay SEPARATE: a device plane carries hierarchical timelines
    ("Steps" > "XLA Modules" > "XLA Ops") whose events nest — summing across
    lines would double-count every op inside its module inside its step."""
    name = ""
    metadata = _parse_event_metadata(plane_buf)
    lines: Dict[str, Dict[str, List[float]]] = {}
    for field, _, value in _fields(plane_buf):
        if field == 2:
            name = bytes(value).decode("utf-8", "replace")
        elif field == 3:  # XLine — one pass; field order is not guaranteed,
            # so aggregate locally and resolve the line name at the end
            line_name = ""
            display_name = ""
            line_agg: Dict[str, List[float]] = {}
            for f2, _, v2 in _fields(value):
                if f2 == 2:
                    line_name = bytes(v2).decode("utf-8", "replace")
                elif f2 == 11:
                    display_name = bytes(v2).decode("utf-8", "replace")
                elif f2 == 4:  # XEvent
                    meta_id = 0
                    dur_ps = 0
                    occurrences = 1
                    for f3, _, v3 in _fields(v2):
                        if f3 == 1:
                            meta_id = v3
                        elif f3 == 3:
                            dur_ps = v3
                        elif f3 == 5:
                            occurrences = v3
                    op = metadata.get(meta_id, f"#{meta_id}")
                    entry = line_agg.setdefault(op, [0.0, 0])
                    entry[0] += dur_ps / 1e9  # ps -> ms
                    entry[1] += occurrences
            agg = lines.setdefault(line_name or display_name, {})
            for op, (ms, cnt) in line_agg.items():
                entry = agg.setdefault(op, [0.0, 0])
                entry[0] += ms
                entry[1] += cnt
    return name, lines


def find_xplane_files(logdir: str) -> List[str]:
    """All ``*.xplane.pb`` under ``logdir`` (jax writes
    ``plugins/profile/<run>/<host>.xplane.pb``)."""
    return sorted(
        glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True)
    )


def op_breakdown(
    logdir: str,
    *,
    plane_filter: str = "TPU",
    line_filter: Optional[str] = None,
    top: Optional[int] = None,
) -> List[OpTime]:
    """Aggregate per-op device time across every matching device plane under
    ``logdir``, sorted by total time descending.

    ``plane_filter`` substring-matches plane names (``"/device:TPU:0"`` etc.);
    pass ``""`` to aggregate every plane (host threads included).

    ``line_filter`` substring-matches timeline (XLine) names within a plane.
    Device planes nest their timelines ("Steps" > "XLA Modules" > "XLA Ops"),
    so summing every line would count each op again inside its module and its
    step. The default (None) auto-selects PER PLANE: a plane with an
    "XLA Ops" line contributes only its op-level lines; planes without one
    (host planes — flat thread lines) contribute every line. ``fraction`` is
    each op's share of the aggregated time — with op-level lines and one
    traced step per capture this reads directly as "share of the step".

    Truncated/partially-written plane files (a capture torn by SIGKILL) are
    SKIPPED, not fatal — see :func:`op_breakdown_with_errors` for the count."""
    rows, _ = op_breakdown_with_errors(
        logdir, plane_filter=plane_filter, line_filter=line_filter, top=top
    )
    return rows


def op_breakdown_with_errors(
    logdir: str,
    *,
    plane_filter: str = "TPU",
    line_filter: Optional[str] = None,
    top: Optional[int] = None,
) -> Tuple[List[OpTime], int]:
    """:func:`op_breakdown` plus the count of plane files skipped as
    corrupt/truncated. A torn capture (profiler killed mid-write — SIGKILL,
    OOM, preemption) leaves a partial ``*.xplane.pb`` whose wire scan raises;
    one torn file must not take down a whole-workdir report, so each file
    parses independently, bad ones are counted and skipped with a warning,
    and the good ones still aggregate. Raises FileNotFoundError only when NO
    plane file exists at all; a logdir where every file is torn returns
    ``([], n_skipped)``."""
    import logging as _logging

    paths = find_xplane_files(logdir)
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {logdir}")
    plane_lines: List[Dict[str, Dict[str, List[float]]]] = []
    skipped = 0
    for path in paths:
        try:
            with open(path, "rb") as f:
                space = f.read()
            file_planes = []
            for field, _, value in _fields(space):
                if field != 1:
                    continue
                # resolve the name from the plane's top-level fields before
                # parsing the body: payloads are length-skipped memoryviews,
                # so rejecting a plane (host threads on TPU, the event-less
                # /host:metadata plane everywhere) costs O(#fields), not
                # O(bytes) — on a 4 MB CPU capture that is ~40% of the parse
                if plane_filter and plane_filter not in _plane_name(value):
                    continue
                name, lines = _parse_plane(value)
                if plane_filter and plane_filter not in name:
                    continue
                file_planes.append(lines)
            # all-or-nothing per file: a plane scanned before the tear must
            # not half-contribute a file the count reports as skipped
            plane_lines.extend(file_planes)
        except (ValueError, IndexError, OSError) as e:
            # IndexError: _read_varint ran off the end of a truncated buffer;
            # ValueError: overflow / unsupported wire type mid-garbage
            skipped += 1
            _logging.getLogger(__name__).warning(
                "skipping truncated/corrupt plane file %s: %s", path, e
            )
    agg: Dict[str, List[float]] = {}
    for lines in plane_lines:
        effective_filter = line_filter
        auto_selected = False
        if effective_filter is None and any("XLA Ops" in line for line in lines):
            effective_filter = "XLA Ops"
            auto_selected = True
        # TPU device planes carry BOTH an 'XLA Ops' line (the serialized
        # TensorCore timeline — sums to the step wall) and an 'Async XLA
        # Ops' line (copy-start/done spans that OVERLAP compute; on the
        # 2026-08-01 v5e capture it summed to 7x the wall). A substring
        # match would fold both and invent a giant copy bucket, so whenever
        # the requested filter names an existing line EXACTLY — auto-selected
        # or user-supplied — only that line contributes; and in substring
        # mode Async timelines are skipped outright — auto-selected OR
        # user-supplied (a user filter like "XLA" or "Ops" must not fold the
        # overlapping async spans in through the side door) — UNLESS the
        # user's filter itself names Async, which is the one way to opt into
        # aggregating those spans deliberately.
        exact_only = effective_filter is not None and any(
            line == effective_filter for line in lines
        )
        skip_async = auto_selected or (
            effective_filter is not None and "Async" not in effective_filter
        )
        for line_name, line_agg in lines.items():
            if exact_only:
                if line_name != effective_filter:
                    continue
            elif effective_filter and effective_filter not in line_name:
                continue
            elif skip_async and "Async" in line_name:
                continue
            for op, (ms, cnt) in line_agg.items():
                entry = agg.setdefault(op, [0.0, 0])
                entry[0] += ms
                entry[1] += cnt
    total = sum(ms for ms, _ in agg.values()) or 1.0
    rows = [
        OpTime(name=op, total_ms=round(ms, 4), occurrences=int(cnt),
               fraction=round(ms / total, 4))
        for op, (ms, cnt) in agg.items()
    ]
    rows.sort(key=lambda r: -r.total_ms)
    return (rows[:top] if top else rows), skipped


def plane_names(logdir: str) -> List[str]:
    """Every plane name in the capture (pick the device plane to filter on)."""
    names = []
    for path in find_xplane_files(logdir):
        try:
            with open(path, "rb") as f:
                space = f.read()
            for field, _, value in _fields(space):
                if field == 1:
                    for f2, _, v2 in _fields(value):
                        if f2 == 2:
                            names.append(bytes(v2).decode("utf-8", "replace"))
                            break
        except (ValueError, IndexError, OSError):
            continue  # torn capture — same stance as op_breakdown
    return names


# the default grouped_breakdown buckets, public because the roofline
# classifier (obs/profiler.py) keys its compute/HBM/collective split on the
# SAME bucket names — one bucketing, two consumers
DEFAULT_GROUPS: Dict[str, Tuple[str, ...]] = {
    # Pallas kernels surface in device traces under their kernel function
    # name ("_qmm_kernel", "_qconv_kernel", ...). The int8 matmul/conv run
    # the MXU just like their XLA counterparts, so they must land in the
    # compute buckets the roofline classifier keys on; "qconv" is caught by
    # the "conv" needle, "qmm" needs its own. The fused epilogue/mask heads
    # are single-HBM-pass elementwise work — same class as XLA fusions.
    "conv": ("convolution", "conv"),
    "matmul": ("dot", "einsum", "qmm"),
    "fusion(elementwise/bn)": ("fusion", "fused_bias_act", "sigmoid_mask"),
    "collectives": (
        "all-reduce",
        "all-gather",
        "reduce-scatter",
        "collective-permute",
        "all-to-all",
        "collective-broadcast",
        "ragged-all-to-all",
    ),
    "reduce": ("reduce",),
    "copy/transpose": ("copy", "transpose", "bitcast"),
    "infeed/outfeed": ("infeed", "outfeed"),
}


def classify_bucket(op_name: str) -> str:
    """The :data:`DEFAULT_GROUPS` bucket ``op_name`` falls into (first hit in
    insertion order, ``"other"`` when none matches) — per-op form of
    :func:`grouped_breakdown`."""
    lowered = op_name.lower()
    for bucket, needles in DEFAULT_GROUPS.items():
        if any(n in lowered for n in needles):
            return bucket
    return "other"


def grouped_breakdown(
    rows: List[OpTime], groups: Optional[Dict[str, Tuple[str, ...]]] = None
) -> Dict[str, float]:
    """Fold an op breakdown into coarse buckets by substring match (first hit
    wins, in insertion order) — the "where does the time go" summary.

    Cross-chip/cross-host collectives get their OWN bucket, listed before the
    generic ``reduce`` needles so all-reduce/all-gather/reduce-scatter/
    collective-permute/all-to-all time is separated from compute: on a
    multi-host capture a fat ``collectives`` bucket with healthy per-host
    step times reads as a slow NETWORK, where a straggling host shows up in
    the fleet report's per-host skew instead (obs/fleet.py)."""
    groups = groups or DEFAULT_GROUPS
    out = {k: 0.0 for k in groups}
    out["other"] = 0.0
    for row in rows:
        lowered = row.name.lower()
        for bucket, needles in groups.items():
            if any(n in lowered for n in needles):
                out[bucket] += row.total_ms
                break
        else:
            out["other"] += row.total_ms
    return {k: round(v, 3) for k, v in out.items() if v}


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("logdir")
    parser.add_argument("--plane", default="TPU", help="plane-name substring filter")
    parser.add_argument(
        "--line", default=None,
        help="timeline-name substring filter (default: auto — op-level lines "
        "only when the plane has an 'XLA Ops' line)",
    )
    parser.add_argument("--top", type=int, default=25)
    parser.add_argument("--json", action="store_true", help="machine-readable output")
    args = parser.parse_args(argv)

    rows = op_breakdown(args.logdir, plane_filter=args.plane, line_filter=args.line)
    if args.json:
        print(json.dumps({
            "planes": plane_names(args.logdir),
            "groups": grouped_breakdown(rows),
            "top_ops": [dataclasses.asdict(r) for r in rows[: args.top]],
        }))
        return 0
    print("planes:", ", ".join(plane_names(args.logdir)))
    print("\nbuckets (ms):")
    for bucket, ms in grouped_breakdown(rows).items():
        print(f"  {bucket:<24} {ms:>10.3f}")
    print(f"\ntop {args.top} ops:")
    for row in rows[: args.top]:
        print(f"  {row.total_ms:>10.3f} ms  x{row.occurrences:<6} "
              f"{row.fraction:>6.1%}  {row.name}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
