"""Root conftest: force an 8-device CPU platform BEFORE the jax backend initializes, so
every multi-device test runs the real sharded code path without TPU hardware (the
fake-backend layer the reference lacked — SURVEY §4).

Note: this environment pre-imports jax via a sitecustomize with JAX_PLATFORMS=axon, so
plain env vars are too late; ``jax.config.update`` still works because the backend
itself initializes lazily at first device query.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the test suite's wall time is dominated by XLA
# compiles of the shard_map-ped train/eval/predict steps; caching them across runs
# cuts repeat-suite time by minutes. Keyed by HLO hash, so stale entries are
# impossible — only disk space is spent. TFDL_NO_COMPILE_CACHE=1 opts out:
# XLA:CPU AOT serialization is machine-feature-sensitive (entries written on a
# different host warn on load and can SIGILL) and one serialization segfault
# inside jax's put_executable_and_time was observed on a 1-core driver box —
# when the cache misbehaves, correctness beats repeat-run speed.
if not os.environ.get("TFDL_NO_COMPILE_CACHE"):
    _cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


# Long full-suite runs accumulate hundreds of live XLA:CPU executables in one
# process; on a small (1-core) driver box this has produced a deterministic
# SEGFAULT inside backend_compile_and_load around test ~315 (the compiler
# itself crashing, not a test) while every module passes in isolation.
# Dropping the in-memory jit caches between modules once the process has grown
# past a threshold bounds that accumulation; the occasional recompile is noise
# next to a crashed suite.
import pytest  # noqa: E402

# clear when RSS has GROWN this much since the last clear (not an absolute
# threshold: clear_caches frees heap that glibc never returns to the OS, so
# absolute RSS stays high after a clear and would re-trigger on every test,
# recompiling the whole suite tail)
_RSS_GROWTH_CLEAR_BYTES = 5 << 30
_rss_floor = [0]


@pytest.fixture(autouse=True)
def _bound_live_executables():
    yield
    try:
        import psutil

        rss = psutil.Process().memory_info().rss
    except Exception:
        return
    if _rss_floor[0] == 0:
        _rss_floor[0] = rss
    if rss - _rss_floor[0] > _RSS_GROWTH_CLEAR_BYTES:
        jax.clear_caches()
        _rss_floor[0] = psutil.Process().memory_info().rss
