"""Root conftest: force an 8-device CPU platform BEFORE the jax backend initializes, so
every multi-device test runs the real sharded code path without TPU hardware (the
fake-backend layer the reference lacked — SURVEY §4).

Note: this environment pre-imports jax via a sitecustomize with JAX_PLATFORMS=axon, so
plain env vars are too late; ``jax.config.update`` still works because the backend
itself initializes lazily at first device query.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the test suite's wall time is dominated by XLA
# compiles of the shard_map-ped train/eval/predict steps; caching them across runs
# cuts repeat-suite time by minutes. Keyed by HLO hash, so stale entries are
# impossible — only disk space is spent.
_cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
